"""Tortoise scenario suite — reference-test ports (VERDICT r2 item 8).

Each case names the reference scenario it mirrors (tortoise/
tortoise_test.go, tortoise/threshold.go semantics, tortoise/sim/
partition+outage shapes).  Cases drive the public surface: on_block /
on_ballot / on_hare_output / on_weak_coin / on_malfeasance /
tally_votes / encode_votes / updates.
"""

from spacemesh_tpu.consensus.tortoise import EMPTY, FULL, VERIFYING, Tortoise
from spacemesh_tpu.core.types import Ballot, Opinion
from spacemesh_tpu.storage.cache import AtxCache, AtxInfo

LPE = 4


def _cache(weight=100, epochs=8):
    cache = AtxCache()
    for e in range(epochs):
        cache.add(e, b"atx-%02d" % e + bytes(26), AtxInfo(
            node_id=b"n" * 32, weight=weight * LPE, base_height=0, height=1,
            num_units=1, vrf_nonce=0, vrf_public_key=b"n" * 32))
    return cache


def _ballot(bid, layer, opinion, node=b"n"):
    return Ballot(layer=layer, atx_id=bytes(32),
                  node_id=(node * 32)[:32], epoch_data=None,
                  ref_ballot=bytes(32), opinion=opinion, eligibilities=[],
                  signature=bid.ljust(64, b"\0"))


def _bid(i):
    return b"S%07d" % i + bytes(24)


def _blk(layer, j=0):
    return b"Q%03d-%02d" % (layer, j) + bytes(25)


def _support(bid, layer, blocks, node, weight=100, base=EMPTY, against=(),
             abstain=()):
    return _ballot(bid, layer, Opinion(base=base, support=list(blocks),
                                       against=list(against),
                                       abstain=list(abstain)), node), weight


def _mk(weight=100, **kw):
    args = dict(hdist=3, zdist=2, window=100)
    args.update(kw)
    return Tortoise(_cache(weight=weight), LPE, **args)


# 1 -- reference TestAbstain: abstaining ballots keep a layer undecided
def test_abstain_keeps_layer_undecided_within_hdist():
    t = _mk()
    b1 = _blk(1)
    t.on_block(1, b1)
    for i, layer in enumerate(range(2, 5)):
        blt, w = _support(_bid(i), layer, [], node=b"%02d" % i,
                          abstain=[1])
        t.on_ballot(blt, weight=w)
    t.tally_votes(4)
    assert t.verified < 1, "abstained layer must not verify"


# 2 -- reference TestAbstainLateBlock / healing: abstain past
#      hdist+zdist forces full-mode decision
def test_abstain_past_zdist_heals_to_a_decision():
    # support ABOVE the local threshold but BELOW the global one, so the
    # decision can only come from full-mode healing past hdist+zdist
    t = _mk(weight=10)
    b1 = _blk(1)
    t.on_block(1, b1)
    for i, layer in enumerate(range(2, 10)):
        blt, w = _support(_bid(i), layer, [b1], node=b"%02d" % i, weight=2)
        t.on_ballot(blt, weight=w)
    t.tally_votes(9)
    assert t.mode == FULL
    assert t.verified >= 1
    assert t.is_valid(b1)


# 3 -- reference TestEncodeVotes: opinions encode support within hdist
def test_encode_votes_supports_hare_output():
    t = _mk()
    b1 = _blk(1)
    t.on_block(1, b1)
    t.on_hare_output(1, b1)
    op = t.encode_votes(2)
    assert b1 in op.support
    assert 1 not in op.abstain


# 4 -- reference TestEncodeVotes (undecided): no hare output within
#      hdist -> abstain on that layer
def test_encode_votes_abstains_on_undecided_layer():
    t = _mk()
    b1 = _blk(1)
    t.on_block(1, b1)  # no hare output recorded
    op = t.encode_votes(2)
    assert 1 in op.abstain
    assert b1 not in op.support and b1 not in op.against


# 5 -- reference TestCountOnBallot: a duplicate ballot id counts once
def test_duplicate_ballot_counts_once():
    t = _mk()
    b1 = _blk(1)
    t.on_block(1, b1)
    blt, w = _support(_bid(0), 2, [b1], node=b"aa", weight=100)
    t.on_ballot(blt, weight=w)
    t.on_ballot(blt, weight=w)  # replay
    ids, margins = t._margins(1, 3)
    assert int(margins[ids.index(b1)]) == 100


# 6 -- reference TestSwitchMode: healing flips to FULL, fresh
#      within-window agreement returns to VERIFYING
def test_mode_switches_full_then_back_to_verifying():
    t = _mk(weight=10)
    b1 = _blk(1)
    t.on_block(1, b1)
    for i, layer in enumerate(range(2, 10)):
        blt, w = _support(_bid(i), layer, [b1], node=b"%02d" % i, weight=2)
        t.on_ballot(blt, weight=w)
    t.tally_votes(9)
    assert t.mode == FULL
    # new layers with hare agreement: verifying again
    for layer in range(9, 12):
        b = _blk(layer)
        t.on_block(layer, b)
        t.on_hare_output(layer, b)
    for i, layer in enumerate(range(10, 13)):
        blt, w = _support(_bid(100 + i), layer, [_blk(layer - 1)],
                          node=b"%03d" % i, weight=40)
        t.on_ballot(blt, weight=w)
    t.tally_votes(12)
    assert t.mode == VERIFYING


# 7 -- threshold.go margin crossing: support below the global threshold
#      does not verify inside the window; above it does
def test_global_threshold_margin_crossing():
    t = _mk(weight=1000)
    b1 = _blk(1)
    t.on_block(1, b1)  # no hare output: margins alone must decide
    glob = t._threshold(1, 3)
    blt, w = _support(_bid(0), 2, [b1], node=b"aa", weight=glob - 1)
    t.on_ballot(blt, weight=w)
    t.tally_votes(3)
    under = t.verified
    blt, w = _support(_bid(1), 2, [b1], node=b"bb", weight=2)
    t.on_ballot(blt, weight=w)  # crosses the threshold
    t.tally_votes(3)
    assert t.verified >= 1
    assert under < 1, "sub-threshold margin must not have verified"


# 8 -- tortoise/sim partition: two cohorts back different blocks; the
#      heavier cohort's block wins after healing
def test_partition_weightier_cohort_wins():
    t = _mk(weight=10)
    a, b = _blk(1, 0), _blk(1, 1)
    t.on_block(1, a)
    t.on_block(1, b)
    for i, layer in enumerate(range(2, 10)):
        blt, w = _support(_bid(i), layer, [a], node=b"%02d" % i, weight=60,
                          against=[b])
        t.on_ballot(blt, weight=w)
        blt, w = _support(_bid(100 + i), layer, [b], node=b"%03d" % i,
                          weight=40, against=[a])
        t.on_ballot(blt, weight=w)
    t.tally_votes(9)
    assert t.is_valid(a)
    assert not t.is_valid(b)


# 9 -- tortoise/sim outage: a cohort goes silent; the survivors' weight
#      still heals the chain
def test_outage_survivor_weight_heals():
    t = _mk(weight=10)
    b1 = _blk(1)
    t.on_block(1, b1)
    # only layers 2..4 have ballots (outage after), then traffic resumes
    for i, layer in enumerate(range(2, 5)):
        blt, w = _support(_bid(i), layer, [b1], node=b"%02d" % i, weight=50)
        t.on_ballot(blt, weight=w)
    t.tally_votes(4)
    for i, layer in enumerate(range(8, 11)):  # resume after the gap
        blt, w = _support(_bid(200 + i), layer, [b1], node=b"%03d" % i,
                          weight=50)
        t.on_ballot(blt, weight=w)
    t.tally_votes(10)
    assert t.verified >= 1
    assert t.is_valid(b1)


# 10 -- reference TestOnMalfeasance mid-window: an equivocator whose
#       weight was load-bearing flips the decision on re-tally
def test_malfeasance_flips_marginal_decision():
    t = _mk(weight=10)
    b1 = _blk(1)
    t.on_block(1, b1)
    evil = b"ee" * 16
    for i, layer in enumerate(range(2, 10)):
        node = b"ee" if i % 2 == 0 else b"%02d" % i
        blt, w = _support(_bid(i), layer, [b1], node=node, weight=30)
        t.on_ballot(blt, weight=w)
    # against-votes from honest minority
    for i, layer in enumerate(range(2, 10)):
        blt, w = _support(_bid(300 + i), layer, [], node=b"%03d" % (500 + i),
                          weight=20, against=[b1])
        t.on_ballot(blt, weight=w)
    t.tally_votes(9)
    assert t.is_valid(b1)  # 120 for vs 160... supports win via hare? no:
    # 4*30=120 evil + 4*30=120 honest for vs 8*20=160 against -> +80
    t.on_malfeasance(evil)
    t.tally_votes(9)
    # without the equivocator: 120 for vs 160 against -> against
    assert not t.is_valid(b1)


# 11 -- reference TestWeakCoin healing tie: covered in
#       test_tortoise.py::test_healing_zero_margin_decided_by_weak_coin;
#       here the OPPOSITE coin must invalidate
def test_weak_coin_false_rejects_tied_block():
    t = _mk(weight=10_000)
    b1 = _blk(1)
    t.on_block(1, b1)
    t.on_weak_coin(7, False)  # the newest coin at-or-before last-1
    blt, w = _support(_bid(0), 2, [b1], node=b"aa", weight=5)
    t.on_ballot(blt, weight=w)  # negligible margin: tie
    t.tally_votes(8)
    assert t.verified >= 1
    assert not t.is_valid(b1), "coin=false must decide against"


# 12 -- reference TestUpdates: decided layers surface exactly once via
#       updates(), with validity flags
def test_updates_surface_decisions_once():
    t = _mk()
    b1 = _blk(1)
    t.on_block(1, b1)
    t.on_hare_output(1, b1)
    blt, w = _support(_bid(0), 2, [b1], node=b"aa", weight=400)
    t.on_ballot(blt, weight=w)
    t.tally_votes(3)
    ups = t.updates()
    assert any(u.block_id == b1 and u.valid for u in ups)
    assert t.updates() == [], "updates must drain"


# 13 -- late block (reference TestLateBlock): a block arriving after
#       its layer verified still gets a validity verdict on re-tally
def test_late_block_revalidated():
    t = _mk()
    b1 = _blk(1)
    t.on_block(1, b1)
    t.on_hare_output(1, b1)
    blt, w = _support(_bid(0), 2, [b1], node=b"aa", weight=400)
    t.on_ballot(blt, weight=w)
    t.tally_votes(3)
    assert t.verified >= 1
    late = _blk(1, 7)
    t.on_block(1, late)  # nobody supports it
    t.tally_votes(3)
    assert not t.is_valid(late)
    assert t.is_valid(b1)
