"""Randomized multi-node tortoise convergence model (VERDICT r3 weak 3).

Mirrors the reference's model runner (reference tortoise/model/runner.go
+ core.go + runner_test.go TestBasicModel): a cluster of independent
tortoise instances ("cores") driven layer by layer through a lossy
messenger — shared blocks/hare/beacon (the reference's hare and beacon
models are reliable singletons), per-smesher ballots built from each
owner core's own encode_votes and delivered with random per-receiver
drops. A verified-frontier monitor asserts after EVERY layer that each
core keeps verifying (within the grace the reference monitor allows) and
that all cores agree on the validity of verified blocks.

The run is fully seeded: any failure replays identically.
"""

import random

import pytest

from spacemesh_tpu.consensus.tortoise import EMPTY, Tortoise
from spacemesh_tpu.core.types import Ballot, Opinion
from spacemesh_tpu.storage.cache import AtxCache, AtxInfo

LPE = 4
HDIST = 4
NODES = 8
SMESHERS_PER_NODE = 3
LAYERS = 24
BALLOT_DROP = 0.05      # per (ballot, receiver) — runner.go failable
HARE_FAIL = 0.1         # whole-layer hare failure
WEIGHT = 120


def _mk_cluster(seed):
    rng = random.Random(seed)
    cache = AtxCache()
    smeshers = []
    for n in range(NODES):
        for s in range(SMESHERS_PER_NODE):
            node_id = b"N%02d-%02d" % (n, s) + bytes(26)
            smeshers.append((n, node_id))
    for epoch in range(LAYERS // LPE + 2):
        for _, node_id in smeshers:
            cache.add(epoch, b"atx-%02d" % epoch + node_id[:26],
                      AtxInfo(node_id=node_id, weight=WEIGHT * LPE,
                              base_height=0, height=1, num_units=1,
                              vrf_nonce=0, vrf_public_key=node_id))
    cores = [Tortoise(cache, LPE, hdist=HDIST, zdist=2, window=200)
             for _ in range(NODES)]
    return rng, cache, cores, smeshers


def _ballot(node_id, layer, j, opinion):
    return Ballot(layer=layer, atx_id=bytes(32), node_id=node_id,
                  epoch_data=None, ref_ballot=bytes(32), opinion=opinion,
                  eligibilities=[],
                  signature=(b"B%02d" % j).ljust(64, b"\0"))


@pytest.mark.parametrize("seed", [1001, 2024, 77])
def test_lossy_cluster_converges(seed):
    rng, cache, cores, smeshers = _mk_cluster(seed)
    blocks_by_layer = {}

    for layer in range(1, LAYERS + 1):
        # shared block production (reference core.go MessageBlock):
        # every core sees the same candidate blocks
        blocks = [b"K%03d-%02d" % (layer, j) + bytes(25)
                  for j in range(rng.randrange(1, 4))]
        blocks_by_layer[layer] = blocks
        for t in cores:
            for b in blocks:
                t.on_block(layer, b)
        # shared hare (reference hare.go is a reliable singleton); it
        # fails whole layers with some probability
        if rng.random() > HARE_FAIL:
            out = rng.choice(blocks)
            for t in cores:
                t.on_hare_output(layer, out)
        else:
            for t in cores:
                t.on_hare_output(layer, EMPTY)
        # per-smesher ballots: built from the OWNER core's view, then
        # delivered to each core independently with drop probability
        # (runner.go failable(MessageBallot{}))
        for j, (owner, node_id) in enumerate(smeshers):
            opinion = cores[owner].encode_votes(layer)
            ballot = _ballot(node_id, layer, j * LAYERS + layer, opinion)
            for t in cores:
                if rng.random() < BALLOT_DROP:
                    continue
                t.on_ballot(ballot, WEIGHT)
        for t in cores:
            t.tally_votes(layer)

        # --- monitor (reference runner_test.go verifiedMonitor) -----
        if layer > HDIST + 2:
            for i, t in enumerate(cores):
                assert t.verified >= layer - HDIST - 2, \
                    f"seed {seed}: core {i} stalled at {t.verified} " \
                    f"in layer {layer}"

    # terminal agreement: on every layer verified by ALL cores, every
    # core holds the same per-block validity verdicts
    frontier = min(t.verified for t in cores)
    assert frontier >= LAYERS - HDIST - 2
    for layer in range(1, frontier + 1):
        verdicts = {tuple(t.is_valid(b) for b in blocks_by_layer[layer])
                    for t in cores}
        assert len(verdicts) == 1, \
            f"seed {seed}: validity split at layer {layer}: {verdicts}"


def test_model_heals_after_hare_outage(seed=5005):
    """A run of consecutive hare failures (all-empty layers) must not
    stall verification once hare recovers — the cores vote each other
    past the outage (reference tortoise/full.go healing)."""
    rng, cache, cores, smeshers = _mk_cluster(seed)
    outage = range(6, 9)

    for layer in range(1, 16):
        blocks = [b"K%03d-%02d" % (layer, j) + bytes(25)
                  for j in range(2)]
        for t in cores:
            for b in blocks:
                t.on_block(layer, b)
        out = EMPTY if layer in outage else blocks[0]
        for t in cores:
            t.on_hare_output(layer, out)
        for j, (owner, node_id) in enumerate(smeshers):
            opinion = cores[owner].encode_votes(layer)
            ballot = _ballot(node_id, layer, j * 100 + layer, opinion)
            for t in cores:
                if rng.random() < BALLOT_DROP:
                    continue
                t.on_ballot(ballot, WEIGHT)
        for t in cores:
            t.tally_votes(layer)

    for i, t in enumerate(cores):
        assert t.verified >= 15 - HDIST - 2, \
            f"core {i} never recovered: verified={t.verified}"
    # blocks of outage layers resolved the same way everywhere
    for layer in outage:
        verdicts = {tuple(t.is_valid(b"K%03d-%02d" % (layer, j) + bytes(25))
                          for j in range(2)) for t in cores}
        assert len(verdicts) == 1
