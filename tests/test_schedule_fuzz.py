"""Schedule-interleaving fuzz: consensus under a shuffled scheduler
(VERDICT r3 aux — race detection analogue; reference runs its whole
suite under `go test -race` with nondeterministic goroutine schedules,
SURVEY §5.2).

asyncio's cooperative model removes data races but not ORDERING bugs:
code that silently relies on two tasks resuming in FIFO order behaves
identically on every normal run. ChaosClockLoop shuffles the ready
queue with a seeded RNG (timers keep their deadlines, so time causality
holds); a full two-smesher consensus scenario must still converge under
several seeds, and any failure replays exactly from its seed.
"""

import asyncio
import hashlib
import pathlib

import pytest

from spacemesh_tpu.core.signing import EdSigner
from spacemesh_tpu.node import clock as clock_mod
from spacemesh_tpu.node.app import App
from spacemesh_tpu.node.config import load
from spacemesh_tpu.p2p.pubsub import LoopbackHub, PubSub
from spacemesh_tpu.p2p.server import LoopbackNet
from spacemesh_tpu.storage import blocks as blockstore
from spacemesh_tpu.storage import layers as layerstore
from spacemesh_tpu.utils.vclock import ChaosClockLoop, cancel_all_tasks

LPE = 3
LAYER_SEC = 2.0
UNTIL = 3 * LPE
GENESIS_PLACEHOLDER = 1_700_002_000.0


def _config(tmp, name):
    return load("standalone", overrides={
        "data_dir": str(tmp / name),
        "layer_duration": LAYER_SEC,
        "layers_per_epoch": LPE,
        "slots_per_layer": 2,
        "genesis": {"time": GENESIS_PLACEHOLDER},
        "post": {"labels_per_unit": 256, "scrypt_n": 2, "k1": 64, "k2": 8,
                 "k3": 4, "min_num_units": 1,
                 "pow_difficulty": "20" + "ff" * 31},
        "smeshing": {"start": True, "num_units": 1, "init_batch": 128},
        "hare": {"committee_size": 20, "round_duration": 0.2,
                 "preround_delay": 0.5, "iteration_limit": 2},
        "beacon": {"proposal_duration": 0.2},
        "tortoise": {"hdist": 4, "window_size": 50},
    })


@pytest.mark.parametrize("seed", [11, 4242])
def test_consensus_converges_under_shuffled_scheduler(seed, tmp_path):
    loop = ChaosClockLoop(seed)
    hub = LoopbackHub()
    net = LoopbackNet()
    apps = []
    for name in ("a", "b"):
        cfg = _config(tmp_path, f"{name}{seed}")
        key_dir = pathlib.Path(cfg.data_dir) / "identities"
        key_dir.mkdir(parents=True, exist_ok=True)
        s = EdSigner(seed=hashlib.sha256(
            f"fuzz-{name}".encode()).digest(), prefix=cfg.genesis.genesis_id)
        (key_dir / "local.key").write_text(s.private_bytes().hex())
        ps = PubSub(node_name=s.node_id)
        hub.join(ps)
        app = App(cfg, signer=s, pubsub=ps, time_source=loop.time)
        app.connect_network(net)
        apps.append(app)
    a, b = apps

    async def go():
        await asyncio.gather(a.prepare(), b.prepare())
        genesis = loop.time() + 1.0
        for app in apps:
            app.clock = clock_mod.LayerClock(genesis, LAYER_SEC,
                                             time_source=loop.time)
        await asyncio.gather(a.run(until_layer=UNTIL),
                             b.run(until_layer=UNTIL))

    try:
        loop.run_until_complete(asyncio.wait_for(go(), 10_000))
    finally:
        loop.run_until_complete(cancel_all_tasks())
        loop.close()

    # the shuffled schedule must not change consensus outcomes
    assert layerstore.last_applied(a.state) >= UNTIL - 2
    assert layerstore.last_applied(b.state) >= UNTIL - 2
    produced = [lyr for lyr in range(LPE, UNTIL + 1)
                if blockstore.ids_in_layer(a.state, lyr)]
    assert produced, f"seed {seed}: no blocks at all"
    for lyr in produced:
        assert blockstore.ids_in_layer(a.state, lyr) \
            == blockstore.ids_in_layer(b.state, lyr), \
            f"seed {seed}: nodes diverged at layer {lyr}"
