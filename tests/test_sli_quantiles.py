"""Windowed-SLI math (obs/sli.py): bucket-delta quantile interpolation
against exact quantiles of known distributions, empty-window and
counter-reset edge cases, the registry sample/collector hooks, and the
histogram bucket-mismatch guard (ISSUE 7 satellites)."""

import math

import pytest

from spacemesh_tpu.obs import sli
from spacemesh_tpu.utils import metrics as metrics_mod


def _hist_counts(bounds, samples):
    """Cumulative le-bucket counts the way utils.metrics.Histogram
    records them."""
    counts = [0] * len(bounds)
    for v in samples:
        for i, b in enumerate(bounds):
            if v <= b:
                counts[i] += 1
    return counts


def _exact_quantile(samples, q):
    s = sorted(samples)
    return s[min(int(q * len(s)), len(s) - 1)]


# --- quantile_from_buckets ---------------------------------------------


def test_quantile_uniform_distribution():
    """Uniform samples: interpolation error is bounded by one bucket
    width around the exact quantile."""
    bounds = (0.1, 0.2, 0.4, 0.6, 0.8, 1.0, float("inf"))
    samples = [i / 1000 for i in range(1, 1001)]  # uniform (0, 1]
    counts = _hist_counts(bounds, samples)
    for q in (0.5, 0.95, 0.99):
        est = sli.quantile_from_buckets(bounds, counts, q)
        exact = _exact_quantile(samples, q)
        # the estimate lives in the same bucket as the exact quantile
        lo = max([0.0] + [b for b in bounds if b < exact])
        hi = min(b for b in bounds if b >= exact)
        assert lo <= est <= hi, (q, est, exact)
        # uniform-in-bucket assumption holds exactly for uniform data
        assert est == pytest.approx(exact, abs=0.02), (q, est, exact)


def test_quantile_exponential_distribution():
    """A skewed (exponential-ish) distribution: the estimator must stay
    within the bucket that holds the exact quantile."""
    # deterministic exponential via inverse CDF over a lattice
    samples = [-math.log(1 - (i + 0.5) / 4096) / 3.0 for i in range(4096)]
    bounds = (0.05, 0.1, 0.25, 0.5, 1.0, 2.0, 5.0, float("inf"))
    counts = _hist_counts(bounds, samples)
    for q in (0.5, 0.95, 0.99):
        est = sli.quantile_from_buckets(bounds, counts, q)
        exact = _exact_quantile(samples, q)
        lo = max([0.0] + [b for b in bounds if b < exact])
        hi = min(b for b in bounds if b >= exact)
        assert lo <= est <= hi, (q, est, exact)


def test_quantile_empty_and_degenerate():
    bounds = (1.0, 2.0, float("inf"))
    assert sli.quantile_from_buckets(bounds, [0, 0, 0], 0.99) is None
    assert sli.quantile_from_buckets(bounds, [], 0.5) is None
    # everything in the +Inf bucket clamps to the top finite bound
    assert sli.quantile_from_buckets(bounds, [0, 0, 7], 0.99) == 2.0
    # single observation interpolates inside its bucket
    est = sli.quantile_from_buckets(bounds, [1, 1, 1], 0.5)
    assert 0.0 <= est <= 1.0
    with pytest.raises(ValueError):
        sli.quantile_from_buckets(bounds, [1, 1, 1], 1.5)


# --- the sampler over a real registry ----------------------------------


def _mk():
    reg = metrics_mod.Registry()
    h = reg.histogram("lat", buckets=(0.01, 0.1, 1.0, float("inf")))
    c = reg.counter("work_total")
    g = reg.gauge("lag")
    return reg, h, c, g


def test_windowed_quantile_uses_deltas_not_cumulative():
    """Old observations outside the window must not pollute the
    quantile: the second window sees ONLY its own (slow) samples."""
    reg, h, c, g = _mk()
    s = sli.SliSampler(reg, window_s=10.0)
    for _ in range(100):
        h.observe(0.005)         # fast era
    s.sample(0.0)
    for _ in range(10):
        h.observe(0.5)           # slow era, inside the window
    s.sample(8.0)
    spec = sli.SliSpec("lat_p99", "lat", "quantile", q=0.99)
    est = s.compute(spec)
    # cumulative data would put p99 at ~0.005-0.1; the window delta
    # contains only the ten 0.5s observations
    assert 0.1 < est <= 1.0
    # and p50 of the window is in the same slow bucket
    assert s.compute(sli.SliSpec("p50", "lat", "quantile", q=0.5)) > 0.1


def test_empty_window_is_none_not_zero():
    reg, h, c, g = _mk()
    s = sli.SliSampler(reg, window_s=10.0)
    spec = sli.SliSpec("lat_p99", "lat", "quantile", q=0.99)
    assert s.compute(spec) is None          # no snapshots at all
    s.sample(0.0)
    assert s.compute(spec) is None          # single snapshot: no window
    s.sample(5.0)
    assert s.compute(spec) is None          # two snapshots, no samples
    rate = sli.SliSpec("work_rate", "work_total", "rate")
    assert s.compute(rate) == 0.0           # counter exists at zero
    missing = sli.SliSpec("nope", "does_not_exist", "rate")
    assert s.compute(missing) is None


def test_counter_reset_truncates_window():
    """A process restart re-registers counters from zero; the delta must
    become 'since the reset', never negative."""
    reg, h, c, g = _mk()
    s = sli.SliSampler(reg, window_s=60.0)
    c.inc(1000.0)
    s.sample(0.0)
    # simulate restart: fresh registry state under the same sampler
    reg2, h2, c2, g2 = _mk()
    s.registry = reg2
    c2.inc(30.0)
    s.sample(10.0)
    rate = s.compute(sli.SliSpec("work_rate", "work_total", "rate"))
    assert rate == pytest.approx(3.0)       # 30/10, not (30-1000)/10
    # histogram reset: bucket deltas go negative -> use the new counts
    h.observe(0.5)
    h2.observe(0.05)
    s.sample(20.0)
    est = s.compute(sli.SliSpec("p", "lat", "quantile", q=0.5))
    assert est is not None and est <= 0.1


def test_rate_and_gauge_kinds():
    reg, h, c, g = _mk()
    s = sli.SliSampler(reg, window_s=30.0)
    s.sample(0.0)
    c.inc(120.0)
    g.set(0.25)
    s.sample(10.0)
    assert s.compute(
        sli.SliSpec("r", "work_total", "rate")) == pytest.approx(12.0)
    assert s.compute(sli.SliSpec("g", "lag", "gauge")) == 0.25


def test_window_edge_prefers_full_window():
    """With snapshots straddling the window edge, the delta spans a full
    window (latest snapshot at/beyond the edge), not the whole history."""
    reg, h, c, g = _mk()
    s = sli.SliSampler(reg, window_s=10.0)
    c.inc(1000.0)
    s.sample(0.0)       # ancient
    c.inc(10.0)
    s.sample(90.0)      # exactly at the edge of the window ending at 100
    c.inc(10.0)
    s.sample(100.0)
    rate = s.compute(sli.SliSpec("r", "work_total", "rate"))
    assert rate == pytest.approx(1.0)       # 10/10s, not 1020/100s


def test_labelset_filter_and_aggregate():
    reg = metrics_mod.Registry()
    h = reg.histogram("d", buckets=(0.01, 1.0, float("inf")))
    s = sli.SliSampler(reg, window_s=30.0)
    s.sample(0.0)
    h.observe(0.005, kind="sig")
    h.observe(0.5, kind="post")
    s.sample(10.0)
    sig = s.compute(sli.SliSpec("sig", "d", "quantile", q=0.5,
                                labels=(("kind", "sig"),)))
    post = s.compute(sli.SliSpec("post", "d", "quantile", q=0.5,
                                 labels=(("kind", "post"),)))
    agg = s.compute(sli.SliSpec("agg", "d", "quantile", q=0.99))
    assert sig <= 0.01 < post
    assert agg > 0.01                        # aggregate sees both
    none = s.compute(sli.SliSpec("vrf", "d", "quantile", q=0.5,
                                 labels=(("kind", "vrf"),)))
    assert none is None


# --- registry plumbing (satellites) ------------------------------------


def test_histogram_bucket_mismatch_raises():
    reg = metrics_mod.Registry()
    reg.histogram("x", buckets=(1.0, float("inf")))
    with pytest.raises(ValueError, match="buckets"):
        reg.histogram("x", buckets=(2.0, float("inf")))
    # same buckets or unspecified buckets still return the instrument
    assert reg.histogram("x", buckets=(1.0, float("inf"))) is \
        reg.histogram("x")


def test_collector_hook_runs_at_scrape_and_sample():
    reg = metrics_mod.Registry()
    g = reg.gauge("depth")
    state = {"v": 0.0, "calls": 0}

    def collect():
        state["calls"] += 1
        g.set(state["v"])

    reg.add_collector(collect)
    state["v"] = 7.0
    assert "depth 7.0" in reg.expose()
    state["v"] = 3.0
    snap = reg.sample()
    assert snap["depth"] == ("gauge", {(): 3.0})
    assert state["calls"] == 2

    def broken():
        raise RuntimeError("bad hook")

    reg.add_collector(broken)
    reg.expose()                              # one bad hook != dead scrape


def test_runtime_collectors_populate_gauges():
    reg = metrics_mod.Registry()
    rss = reg.gauge("process_resident_memory_bytes")
    fds = reg.gauge("process_open_fds")
    # the module gauges live on the global registry; re-point the
    # collectors at private ones via monkey-free direct calls
    sli._collect_rss()
    sli._collect_fds()
    assert metrics_mod.process_rss_bytes.sample().get((), 0) > 1 << 20
    assert metrics_mod.process_open_fds.sample().get((), 0) > 0
    del rss, fds
