"""Sharded label computation on the virtual 8-device CPU mesh."""

import hashlib

import jax
import numpy as np
import pytest

from spacemesh_tpu.ops import proving, scrypt
from spacemesh_tpu.parallel import (
    data_mesh,
    init_step_sharded,
    labels_with_min_sharded,
    scrypt_labels_sharded,
)

COMMIT = hashlib.sha256(b"c").digest()


def _host_min(labels: np.ndarray) -> tuple[int, bytes]:
    lo = labels[:, :8].copy().view("<u8").ravel()
    hi = labels[:, 8:].copy().view("<u8").ravel()
    k = int(np.lexsort((lo, hi))[0])
    return k, bytes(labels[k])


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8


def test_sharded_labels_match_single_device():
    mesh = data_mesh()
    idx = np.arange(256, dtype=np.uint64)
    lo, hi = scrypt.split_indices(idx)
    cw = scrypt.commitment_to_words(COMMIT)
    words = scrypt_labels_sharded(mesh, cw, lo, hi, n=4)
    want = scrypt.scrypt_labels(COMMIT, idx, n=4)
    got = np.frombuffer(scrypt.labels_to_bytes(np.asarray(words)), dtype=np.uint8)
    assert np.array_equal(got.reshape(-1, 16), want)


def test_sharded_multi_identity():
    # 4 identities x 64 labels striped across the mesh, per-lane commitments
    mesh = data_mesh()
    commits = np.stack([
        np.frombuffer(hashlib.sha256(b"id%d" % i).digest(), dtype=np.uint8)
        for i in range(4) for _ in range(64)])
    idx = np.tile(np.arange(64, dtype=np.uint64), 4)
    cw = commits.view(">u4").astype(np.uint32).reshape(-1, 8).T
    lo, hi = scrypt.split_indices(idx)
    words = scrypt_labels_sharded(mesh, cw, lo, hi, n=4)
    got = np.frombuffer(scrypt.labels_to_bytes(np.asarray(words)), dtype=np.uint8)
    got = got.reshape(-1, 16)
    for i in range(4):
        want = scrypt.scrypt_labels(
            hashlib.sha256(b"id%d" % i).digest(),
            np.arange(64, dtype=np.uint64), n=4)
        assert np.array_equal(got[i * 64:(i + 1) * 64], want), f"identity {i}"


def test_init_step_stats():
    mesh = data_mesh()
    total = 512
    idx = np.arange(total, dtype=np.uint64)
    lo, hi = scrypt.split_indices(idx)
    cw = scrypt.commitment_to_words(COMMIT)
    t = proving.threshold_u32(64, total)
    words, qualifying, min_hi, min_lo = init_step_sharded(
        mesh, cw, lo, hi, t, n=2)
    labels = scrypt.scrypt_labels(COMMIT, idx, n=2)
    # qualifying count matches host recount of words[0] < t
    w0 = np.asarray(words)[0]
    assert int(qualifying) == int((w0 < t).sum())
    # min over byteswapped top words equals host min of top-32 LE key
    k_hi = (labels[:, 15].astype(np.uint64) << 24
            | labels[:, 14].astype(np.uint64) << 16
            | labels[:, 13].astype(np.uint64) << 8
            | labels[:, 12].astype(np.uint64))
    assert int(min_hi) == int(k_hi.min())


@pytest.mark.parametrize("ndev", [1, 2, 4])
def test_labels_with_min_sharded_matches_single_device(ndev):
    """scrypt_labels over a 1/2/4-device mesh is bit-identical to the
    single-device path, and the on-device VRF scan lands on the same
    first-occurrence LE-u128 minimum as the host lexsort."""
    total = 512
    idx = np.arange(total, dtype=np.uint64)
    want = scrypt.scrypt_labels(COMMIT, idx, n=4)
    want_k, want_val = _host_min(want)

    mesh = data_mesh(jax.devices()[:ndev])
    cw = scrypt.commitment_to_words(COMMIT)
    carry = scrypt.vrf_carry_init()
    got = []
    for start in range(0, total, 128):  # batched, carry chained across
        lo, hi = scrypt.split_indices(idx[start:start + 128])
        words, carry, snap = labels_with_min_sharded(
            mesh, cw, lo, hi, carry, n=4)
        got.append(np.frombuffer(
            scrypt.labels_to_bytes(np.asarray(words)),
            dtype=np.uint8).reshape(-1, 16))
    assert np.array_equal(np.concatenate(got), want)
    decoded = scrypt.vrf_carry_decode(snap)
    assert decoded is not None
    k, (hi_, lo_) = decoded
    assert k == want_k
    assert (lo_.to_bytes(8, "little") + hi_.to_bytes(8, "little")) == want_val


@pytest.mark.parametrize("ndev", [2, 4])
def test_initializer_sharded_equals_single_device(tmp_path, ndev):
    """A full streaming init over a sub-mesh produces bit-identical label
    files and the same VRF nonce as the single-device init — including a
    final partial batch that does not divide the mesh (pad+trim path)."""
    from spacemesh_tpu.post import initializer
    from spacemesh_tpu.post.data import LabelStore

    node = hashlib.sha256(b"mesh-node").digest()
    total, batch = 649, 256  # final batch of 137 labels: pad+trim on 2 and 4

    def run(sub, mesh):
        d = tmp_path / sub
        meta, _ = initializer.initialize(
            d, node_id=node, commitment=COMMIT, num_units=1,
            labels_per_unit=total, scrypt_n=4, max_file_size=1 << 20,
            batch_size=batch, mesh=mesh)
        store = LabelStore(d, meta)
        return meta, store.read_labels(0, total)

    meta1, bytes1 = run("single", None)
    meshed, bytesn = run(f"mesh{ndev}", data_mesh(jax.devices()[:ndev]))
    assert bytes1 == bytesn
    assert meta1.vrf_nonce == meshed.vrf_nonce
    assert meta1.vrf_nonce_value == meshed.vrf_nonce_value
