"""Sharded label computation on the virtual 8-device CPU mesh."""

import hashlib

import jax
import numpy as np
import pytest

from spacemesh_tpu.ops import proving, scrypt
from spacemesh_tpu.parallel import data_mesh, init_step_sharded, scrypt_labels_sharded

COMMIT = hashlib.sha256(b"c").digest()


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8


def test_sharded_labels_match_single_device():
    mesh = data_mesh()
    idx = np.arange(256, dtype=np.uint64)
    lo, hi = scrypt.split_indices(idx)
    cw = scrypt.commitment_to_words(COMMIT)
    words = scrypt_labels_sharded(mesh, cw, lo, hi, n=4)
    want = scrypt.scrypt_labels(COMMIT, idx, n=4)
    got = np.frombuffer(scrypt.labels_to_bytes(np.asarray(words)), dtype=np.uint8)
    assert np.array_equal(got.reshape(-1, 16), want)


def test_sharded_multi_identity():
    # 4 identities x 64 labels striped across the mesh, per-lane commitments
    mesh = data_mesh()
    commits = np.stack([
        np.frombuffer(hashlib.sha256(b"id%d" % i).digest(), dtype=np.uint8)
        for i in range(4) for _ in range(64)])
    idx = np.tile(np.arange(64, dtype=np.uint64), 4)
    cw = commits.view(">u4").astype(np.uint32).reshape(-1, 8).T
    lo, hi = scrypt.split_indices(idx)
    words = scrypt_labels_sharded(mesh, cw, lo, hi, n=4)
    got = np.frombuffer(scrypt.labels_to_bytes(np.asarray(words)), dtype=np.uint8)
    got = got.reshape(-1, 16)
    for i in range(4):
        want = scrypt.scrypt_labels(
            hashlib.sha256(b"id%d" % i).digest(),
            np.arange(64, dtype=np.uint64), n=4)
        assert np.array_equal(got[i * 64:(i + 1) * 64], want), f"identity {i}"


def test_init_step_stats():
    mesh = data_mesh()
    total = 512
    idx = np.arange(total, dtype=np.uint64)
    lo, hi = scrypt.split_indices(idx)
    cw = scrypt.commitment_to_words(COMMIT)
    t = proving.threshold_u32(64, total)
    words, qualifying, min_hi, min_lo = init_step_sharded(
        mesh, cw, lo, hi, t, n=2)
    labels = scrypt.scrypt_labels(COMMIT, idx, n=2)
    # qualifying count matches host recount of words[0] < t
    w0 = np.asarray(words)[0]
    assert int(qualifying) == int((w0 < t).sum())
    # min over byteswapped top words equals host min of top-32 LE key
    k_hi = (labels[:, 15].astype(np.uint64) << 24
            | labels[:, 14].astype(np.uint64) << 16
            | labels[:, 13].astype(np.uint64) << 8
            | labels[:, 12].astype(np.uint64))
    assert int(min_hi) == int(k_hi.min())
