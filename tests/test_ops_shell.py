"""Operational shell: bootstrap updater + prune loop.

Reference: bootstrap/updater.go (epoch fallback beacon/activeset from an
operator-provided source) and prune/prune.go (retention cleanup).
"""

import json

from spacemesh_tpu.core.types import Certificate
from spacemesh_tpu.node.bootstrap import BootstrapUpdater, Pruner
from spacemesh_tpu.storage import db as dbmod
from spacemesh_tpu.storage import misc as miscstore


def test_bootstrap_applies_beacon_and_activeset(tmp_path):
    src = tmp_path / "fallback.json"
    src.write_text(json.dumps([
        {"epoch": 5, "beacon": "aabbccdd", "activeset": ["11" * 32]},
        {"epoch": 6, "beacon": "deadbeef"},
    ]))
    beacons, sets_ = [], []
    upd = BootstrapUpdater(
        str(src),
        on_beacon=lambda e, b: beacons.append((e, b)),
        on_activeset=lambda e, ids: sets_.append((e, ids)),
        cache_dir=tmp_path / "cache")
    assert upd.poll_once() == 2
    assert beacons == [(5, bytes.fromhex("aabbccdd")),
                       (6, bytes.fromhex("deadbeef"))]
    assert sets_ == [(5, [b"\x11" * 32])]
    # idempotent: same docs are not re-applied
    assert upd.poll_once() == 0
    assert (tmp_path / "cache" / "epoch-5.json").exists()


def test_bootstrap_rejects_malformed(tmp_path):
    src = tmp_path / "bad.json"
    src.write_text(json.dumps([
        {"epoch": 7, "beacon": "toolongbeacon00"},
        {"no_epoch": True},
        {"epoch": 8, "activeset": ["ff"]},
    ]))
    applied = []
    upd = BootstrapUpdater(str(src),
                           on_beacon=lambda e, b: applied.append(e))
    assert upd.poll_once() == 0
    assert applied == []


def test_prune_removes_stale_rows():
    db = dbmod.open_state(":memory:")
    for layer in (1, 2, 50):
        miscstore.add_certificate(
            db, layer, Certificate(block_id=bytes(32), signatures=[]))
    miscstore.add_active_set(db, b"s" * 32, 0, [b"a" * 32])
    miscstore.add_active_set(db, b"t" * 32, 9, [b"a" * 32])
    db.exec("INSERT INTO poet_proofs (ref, poet_id, round_id, ticks, data)"
            " VALUES (?,?,?,?,?)", (b"r" * 32, b"p" * 32, "0", 1, b"x"))
    db.exec("INSERT INTO poet_proofs (ref, poet_id, round_id, ticks, data)"
            " VALUES (?,?,?,?,?)", (b"q" * 32, b"p" * 32, "9", 1, b"x"))

    pruner = Pruner(db, retention_layers=10, current_layer=lambda: 40,
                    layers_per_epoch=3, interval=0.1)
    out = pruner.prune_once()
    assert out["certificates"] == 2          # layers 1, 2 < horizon 30
    assert miscstore.certificate(db, 50) is not None
    assert out["active_sets"] == 1           # epoch 0 < horizon epoch 9
    assert miscstore.active_set(db, b"t" * 32) is not None
    assert out["poet_proofs"] == 1           # round 0 pruned, round 9 kept
    db.close()
