"""Sharded scenario fabric (ISSUE 19, spacemesh_tpu/sim/shard.py).

Barrier math at the unit level (the safe horizon may never release a
frame before its link-delay floor), the W-invariance contract on a
clean-link world (W=1 and W=4 land identical assertion outcomes AND
identical merged digests), and the crash discipline (a worker killed
mid-window is a TYPED scenario failure, never a hang). The full-size
sharded drills (storm-1024 --shards 2, storm-4096, soak-epochs) live in
tests/test_sim_scenarios.py and the storm-smoke CI job.
"""

import time

import pytest

from spacemesh_tpu.sim import builtin, run_scenario
from spacemesh_tpu.sim.net import LinkPolicy, SimNetwork
from spacemesh_tpu.sim import shard as shard_mod
from spacemesh_tpu.sim.shard import (ShardWorker, ShardedMeshHub,
                                     resolve_shards)


@pytest.fixture(autouse=True)
def _no_env_override(monkeypatch):
    monkeypatch.delenv("SPACEMESH_SIM_SHARDS", raising=False)


# --- resolve_shards ----------------------------------------------------


def test_resolve_shards_w1_collapse_and_auto(monkeypatch):
    assert resolve_shards(None, 1000) == 1
    assert resolve_shards("", 1000) == 1
    assert resolve_shards(1, 1000) == 1
    assert resolve_shards("0", 1000) == 1
    # explicit W honored even on a small host (tests force W=4)
    assert resolve_shards(4, 1000) == 4
    # "auto" = min(host cores, lights // 64); too few lights -> 1
    assert resolve_shards("auto", 63) == 1
    cores = len(__import__("os").sched_getaffinity(0))
    assert resolve_shards("auto", 64 * (cores + 2)) == cores
    # env beats the script
    monkeypatch.setenv("SPACEMESH_SIM_SHARDS", "3")
    assert resolve_shards(None, 1000) == 3
    assert resolve_shards("auto", 1000) == 3


def test_resolve_shards_clamps_to_light_population():
    # every worker shard must own at least one light
    assert resolve_shards(64, 2) == 3


# --- barrier math: the delay floor is the lookahead --------------------


def test_min_delay_floor_is_min_over_policies():
    net = SimNetwork(1)
    a, b = b"a" * 32, b"b" * 32
    net.add_node(a)
    net.add_node(b)
    net.default_policy = LinkPolicy(delay=0.05, jitter=0.3)
    assert net.min_delay_floor() == pytest.approx(0.05)
    # a single faster link drags the floor down — jitter never counts
    net.set_link_policy(LinkPolicy(delay=0.01, jitter=0.5), a, b)
    assert net.min_delay_floor() == pytest.approx(0.01)
    net.set_link_policy(LinkPolicy(delay=0.2), a, b)
    assert net.min_delay_floor() == pytest.approx(0.05)


def _two_shard_snap(delay: float) -> dict:
    """A 2-worker world: lights a,b on shard 1, light c on shard 2."""
    a, b, c = b"a" * 32, b"b" * 32, b"c" * 32
    names = [a, b, c]
    adj = {a: [b, c], b: [a, c], c: [a, b]}
    return dict(
        seed=7, degree=6, shards=3, gossip_degree=4, shard=1,
        names=names, adj=adj, group={}, down=[], eclipsed={},
        blocked=[], default_policy=dict(
            loss=0.0, delay=delay, jitter=0.0, dup=0.0, reorder=0.0,
            reorder_delay=0.0),
        link_policy=[], shard_of={a: 1, b: 1, c: 2}, owned=[a, b])


def test_worker_frames_never_beat_the_delay_floor():
    """Every frame a worker emits at instant t arrives at >= t + floor —
    the inequality the safe horizon [N, N+L) leans on."""
    delay = 0.05
    w = ShardWorker(_two_shard_snap(delay))
    t = 1.0
    nxt, out = w.run(t, True, [("publish", t, b"a" * 32, "storm",
                                b"payload")], [])
    # the publish fired and relayed: everything bound for shard 2 is
    # stamped at or after t + floor, and the worker's own wheel holds
    # nothing before it either
    assert w.stats["published"] == 1
    assert out, "no cross-shard frame left the worker"
    assert all(arrival >= t + delay - 1e-12
               for arrival, _, _, _ in out)
    assert nxt >= t + delay - 1e-12


def test_worker_window_is_exclusive_of_the_horizon():
    """run(horizon, inclusive=False) must NOT fire a frame sitting
    exactly at the horizon — that instant belongs to the next window."""
    delay = 0.05
    w = ShardWorker(_two_shard_snap(delay))
    t = 1.0
    frame = ("msg", b"c" * 32, ("storm", b"m" * 32, b"payload"))
    # a frame addressed to owned light a, arriving exactly at t + delay
    nxt, out = w.run(t + delay, False, [],
                     [(t + delay, b"a" * 32, frame)])
    assert w.stats["delivered"] == 0
    assert nxt == pytest.approx(t + delay)
    # granting the instant itself (inclusive settle) delivers it
    nxt, out = w.run(t + delay, True, [], [])
    assert w.stats["delivered"] == 1


# --- W-invariance on a clean-link world --------------------------------


def test_w1_and_w4_agree_on_digest_and_asserts(tmp_path):
    """The loss-free world draws nothing from any link RNG, so flood
    coverage is arrival-order invariant: W=1 (plain in-process fabric)
    and W=4 (three worker subprocesses) must land the IDENTICAL merged
    digest and identical assertion outcomes."""
    results = {}
    for w in (1, 4):
        script = builtin("smoke", light=6)
        script["shards"] = w
        results[w] = run_scenario(script, tmp=tmp_path / f"w{w}")
    r1, r4 = results[1], results[4]
    assert r1.ok, [a for a in r1.asserts if not a["ok"]]
    assert r4.ok, [a for a in r4.asserts if not a["ok"]]
    assert r1.digest == r4.digest
    outcomes1 = [(a["phase"], a["kind"], a["ok"]) for a in r1.asserts]
    outcomes4 = [(a["phase"], a["kind"], a["ok"]) for a in r4.asserts
                 if a["kind"] != "shard_worker"]
    assert outcomes1 == outcomes4


def test_sharded_replay_is_byte_identical(tmp_path):
    """Same (seed, W) => byte-identical digest, W > 1 included."""
    digests = []
    for run in ("a", "b"):
        script = builtin("smoke", light=6)
        script["shards"] = 2
        digests.append(run_scenario(script, tmp=tmp_path / run).digest)
    assert digests[0] == digests[1]


# --- crash discipline --------------------------------------------------


def test_worker_crash_is_typed_failure_not_hang(tmp_path, monkeypatch):
    """Kill a worker process mid-window: the run must come back quickly
    with ok=False and a typed shard_worker assertion — the pipe EOF is
    translated to ShardWorkerCrash, never waited out."""
    calls = {"n": 0}
    orig = ShardedMeshHub._flush_and_run

    def killer(self, need, upto, inclusive):
        calls["n"] += 1
        if calls["n"] == 5:
            self._workers[0].proc.kill()
        return orig(self, need, upto, inclusive)

    monkeypatch.setattr(ShardedMeshHub, "_flush_and_run", killer)
    script = builtin("smoke", light=6)
    script["shards"] = 2
    t0 = time.perf_counter()
    r = run_scenario(script, tmp=tmp_path)
    wall = time.perf_counter() - t0
    assert calls["n"] >= 5, "the fabric never reached the kill window"
    assert not r.ok
    crash = [a for a in r.asserts if a["kind"] == "shard_worker"]
    assert crash and not crash[0]["ok"]
    assert wall < 120.0, f"crash handling took {wall:.0f}s (hang?)"


def test_shard_module_is_importable_without_jax():
    """Workers import spacemesh_tpu.sim.shard in a bare subprocess; a
    jax import at module scope would multiply spawn cost by seconds."""
    import subprocess
    import sys
    code = ("import sys; sys.modules['jax'] = None; "
            "import spacemesh_tpu.sim.shard")
    r = subprocess.run([sys.executable, "-c", code],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
