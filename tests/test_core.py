"""Core primitives: codec canonicality, blake3 vectors, signatures, types."""

import dataclasses
import io

import pytest

from spacemesh_tpu.core import codec, hashing, signing, types


# --- codec -----------------------------------------------------------------


def test_uint_roundtrip_and_bounds():
    for c, width in ((codec.u8, 1), (codec.u16, 2), (codec.u32, 4), (codec.u64, 8)):
        hi = (1 << (8 * width)) - 1
        for v in (0, 1, hi):
            assert codec.decode(codec.encode(v, c), c) == v
        with pytest.raises(ValueError):
            codec.encode(hi + 1, c)


def test_compact_minimal_encoding_enforced():
    for v in (0, 1, 127, 128, 300, 2**32, 2**63):
        data = codec.encode(v, codec.compact)
        assert codec.decode(data, codec.compact) == v
    # 0 encoded with a redundant continuation byte must be rejected
    with pytest.raises(codec.DecodeError):
        codec.decode(b"\x80\x00", codec.compact)
    with pytest.raises(codec.DecodeError):
        codec.decode(b"\xff" * 10 + b"\x01", codec.compact)
    # 10 bytes at full fan-out lands at shift 63 with 7 payload bits —
    # a value up to ~2^70 that the shift guard alone waves through
    with pytest.raises(codec.DecodeError):
        codec.decode(b"\xff" * 9 + b"\x7f", codec.compact)


def test_lying_length_prefix_rejected_not_crashed():
    """A var-bytes length prefix near 2^64 must raise DecodeError, not
    OverflowError out of io.BytesIO.read (gossip fuzz found the crash:
    one bit flip in a valid blob can inflate a compact length past
    index size)."""
    huge = codec.encode((1 << 64) - 1, codec.compact) + b"\x00" * 8
    with pytest.raises(codec.DecodeError):
        codec.decode(huge, codec.var_bytes)


def test_trailing_bytes_rejected():
    data = codec.encode(5, codec.u8) + b"\x00"
    with pytest.raises(codec.DecodeError):
        codec.decode(data, codec.u8)


def test_option_vec_string():
    c = codec.vec(codec.option(codec.string))
    v = ["a", None, "xyz", ""]
    assert codec.decode(codec.encode(v, c), c) == v
    with pytest.raises(codec.DecodeError):
        codec.decode(b"\x02", codec.option(codec.u8))  # invalid tag


def test_bool_strictness():
    assert codec.decode(b"\x01", codec.boolean) is True
    with pytest.raises(codec.DecodeError):
        codec.decode(b"\x02", codec.boolean)


# --- hashing ---------------------------------------------------------------


def test_blake3_official_vectors():
    # official test vectors from the BLAKE3 repository
    assert hashing.sum256(b"").hex() == (
        "af1349b9f5f9a1a6a0404dea36dcc9499bcb25c9adc112b7cc9a93cae41f3262")
    assert hashing.sum256(b"abc").hex() == (
        "6437b3ac38465133ffb63b75273a8db548c558465d79db03fd359c6cd5bd9d85")


def test_blake3_incremental_and_multichunk():
    data = bytes(range(256)) * 17  # > 4 chunks
    one = hashing.sum256(data)
    h = hashing.Hasher()
    for i in range(0, len(data), 100):
        h.update(data[i:i + 100])
    assert h.digest() == one
    assert hashing.sum160(data) == one[:0] + hashing.sum160(data)
    assert len(hashing.sum160(data)) == 20
    assert hashing.sum256(data[:1024]) != hashing.sum256(data[:1025])


def test_blake3_keyed():
    k1 = bytes(32)
    k2 = bytes([1]) + bytes(31)
    assert hashing.keyed(k1, b"m") != hashing.keyed(k2, b"m")
    assert hashing.keyed(k1, b"m") != hashing.sum256(b"m")


# --- signing ---------------------------------------------------------------


def test_ed25519_domains_and_prefix():
    s = signing.EdSigner(prefix=b"net1")
    v = signing.EdVerifier(prefix=b"net1")
    sig = s.sign(signing.Domain.ATX, b"hello")
    assert v.verify(signing.Domain.ATX, s.public_key, b"hello", sig)
    assert not v.verify(signing.Domain.BALLOT, s.public_key, b"hello", sig)
    assert not v.verify(signing.Domain.ATX, s.public_key, b"hellx", sig)
    v2 = signing.EdVerifier(prefix=b"net2")
    assert not v2.verify(signing.Domain.ATX, s.public_key, b"hello", sig)


def test_ed25519_key_persistence():
    s = signing.EdSigner()
    s2 = signing.EdSigner(seed=s.private_bytes())
    assert s2.public_key == s.public_key


def test_vrf_prove_verify():
    s = signing.EdSigner()
    vs = s.vrf_signer()
    vv = signing.VrfVerifier()
    proof = vs.prove(b"alpha")
    assert len(proof) == signing.VRF_PROOF_SIZE
    assert vv.verify(vs.public_key, b"alpha", proof)
    assert not vv.verify(vs.public_key, b"beta", proof)
    other = signing.EdSigner().vrf_signer()
    assert not vv.verify(other.public_key, b"alpha", proof)
    # deterministic + unique output
    assert vs.prove(b"alpha") == proof
    out = signing.vrf_output(proof)
    assert len(out) == signing.VRF_OUTPUT_SIZE
    assert out != signing.vrf_output(vs.prove(b"alpha2"))


def test_vrf_proof_malleability_rejected():
    s = signing.EdSigner().vrf_signer()
    vv = signing.VrfVerifier()
    proof = bytearray(s.prove(b"x"))
    proof[40] ^= 1  # flip a challenge bit
    assert not vv.verify(s.public_key, b"x", bytes(proof))
    assert not vv.verify(s.public_key, b"x", b"\x00" * 80)
    assert not vv.verify(s.public_key, b"x", bytes(10))


# --- types -----------------------------------------------------------------


def _post():
    return types.Post(nonce=3, indices=[1, 5, 9], pow_nonce=42)


def _nipost():
    return types.NIPost(
        membership=types.MerkleProof(leaf_index=2, nodes=[bytes(32), bytes(32)]),
        post=_post(),
        post_metadata=types.PostMetadataWire(challenge=bytes(32),
                                             labels_per_unit=1024))


def test_atx_roundtrip_and_id():
    atx = types.ActivationTx(
        publish_epoch=7, prev_atx=bytes(32), pos_atx=bytes([1]) * 32,
        commitment_atx=bytes([2]) * 32, initial_post=_post(),
        nipost=_nipost(), num_units=4, vrf_nonce=99, vrf_public_key=bytes(32),
        coinbase=bytes(24), node_id=bytes([3]) * 32, signature=bytes(64))
    data = atx.to_bytes()
    back = types.ActivationTx.from_bytes(data)
    assert back == atx
    assert back.id == atx.id
    # id commits to content
    other = dataclasses.replace(atx, num_units=5)
    assert other.id != atx.id
    assert atx.target_epoch() == 8


def test_ballot_proposal_block_roundtrip():
    ballot = types.Ballot(
        layer=12, atx_id=bytes([7]) * 32,
        epoch_data=types.EpochData(beacon=b"\x01\x02\x03\x04",
                                   active_set_root=bytes(32),
                                   eligibility_count=5),
        ref_ballot=bytes(32),
        eligibilities=[types.VotingEligibility(j=0, sig=bytes(80))],
        opinion=types.Opinion(base=bytes(32), support=[bytes([9]) * 32],
                              against=[], abstain=[3]),
        node_id=bytes([1]) * 32, signature=bytes(64))
    assert types.Ballot.from_bytes(ballot.to_bytes()) == ballot

    prop = types.Proposal(ballot=ballot, tx_ids=[bytes([5]) * 32],
                          mesh_hash=bytes(32), signature=bytes(64))
    assert types.Proposal.from_bytes(prop.to_bytes()) == prop

    blk = types.Block(layer=12, tick_height=1000,
                      rewards=[types.Reward(atx_id=bytes([7]) * 32,
                                            coinbase=bytes(24), weight=10)],
                      tx_ids=[bytes([5]) * 32])
    assert types.Block.from_bytes(blk.to_bytes()) == blk
    cert = types.Certificate(
        block_id=blk.id,
        signatures=[types.CertifyMessage(
            layer=12, block_id=blk.id, eligibility_count=1,
            proof=bytes(80), atx_id=bytes(32), node_id=bytes(32),
            signature=bytes(64))])
    assert types.Certificate.from_bytes(cert.to_bytes()) == cert


def test_address_bech32_roundtrip():
    a = types.Address.from_public_key(b"wallet-template", bytes(32))
    s = a.encode()
    assert s.startswith("sm1")
    assert types.Address.decode(s) == a
    with pytest.raises(ValueError):
        types.Address.decode(s[:-1] + ("q" if s[-1] != "q" else "p"))
    with pytest.raises(ValueError):
        types.Address(b"short")


def test_layer_epoch_math():
    lyr = types.LayerID(4032 * 3 + 5)
    assert lyr.epoch(4032) == 3
    assert not lyr.first_in_epoch(4032)
    assert types.epoch_first_layer(3, 4032) == 4032 * 3
    assert types.LayerID(8064).first_in_epoch(4032)
