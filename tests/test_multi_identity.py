"""Multi-identity node: N smeshers in one App (BASELINE config 5 shape).

The reference registers many signers into one activation.Builder and runs
per-signer goroutines (activation.go:218 Register, node_identities.go).
Here: one standalone node hosts 4 identities, each POST-inits, publishes
its own ATX per epoch (shared in-proc poet round), and participates in
hare/beacon/certifier with its own eligibility. Proving goes through the
OUT-OF-PROCESS worker (PostSupervisor + RemotePostClient) to exercise the
node-side seam end to end.
"""

import asyncio

import pytest

from spacemesh_tpu.node import clock as clock_mod
from spacemesh_tpu.node.app import App
from spacemesh_tpu.node.config import load
from spacemesh_tpu.storage import atxs as atxstore
from spacemesh_tpu.storage import blocks as blockstore
from spacemesh_tpu.storage import layers as layerstore
from spacemesh_tpu.utils.vclock import VirtualClockLoop, cancel_all_tasks

LPE = 3
LAYER_SEC = 2.0  # virtual seconds (VirtualClockLoop)
N_IDS = 4


def _config(tmp_path):
    return load("standalone", overrides={
        "data_dir": str(tmp_path / "node"),
        "layer_duration": LAYER_SEC,
        "layers_per_epoch": LPE,
        "slots_per_layer": 2,
        "genesis": {"time": 0.0},  # replaced with virtual time in the run
        "post": {"labels_per_unit": 256, "scrypt_n": 2, "k1": 64, "k2": 8,
                 "k3": 4, "min_num_units": 1,
                 "pow_difficulty": "20" + "ff" * 31},
        "smeshing": {"start": True, "num_units": 1, "init_batch": 128,
                     "num_identities": N_IDS, "external_worker": True},
        "hare": {"committee_size": 40, "round_duration": 0.2,
                 "preround_delay": 0.5, "iteration_limit": 2},
        "beacon": {"proposal_duration": 0.2},
        "tortoise": {"hdist": 4, "window_size": 50},
    })


@pytest.fixture(scope="module")
def ran(tmp_path_factory):
    tmp_path = tmp_path_factory.mktemp("multiid")
    cfg = _config(tmp_path)
    loop = VirtualClockLoop()
    app = App(cfg, time_source=loop.time)

    async def go():
        await app.prepare()
        app.clock = clock_mod.LayerClock(loop.time() + 1.0,
                                         cfg.layer_duration,
                                         time_source=loop.time)
        await asyncio.wait_for(app.run(until_layer=2 * LPE + 1), 10_000)

    try:
        loop.run_until_complete(go())
        yield app
    finally:
        loop.run_until_complete(cancel_all_tasks())
        app.close()


def test_n_identities_created(ran):
    assert len(ran.signers) == N_IDS
    assert len({s.node_id for s in ran.signers}) == N_IDS
    assert len(ran.atx_builders) == N_IDS


def test_every_identity_publishes_atx_per_epoch(ran):
    for epoch in (0, 1):
        for s in ran.signers:
            atx = atxstore.by_node_in_epoch(ran.state, s.node_id, epoch)
            assert atx is not None, (
                f"identity {s.node_id.hex()[:8]} missing epoch-{epoch} ATX")
            assert atx.vrf_public_key == s.node_id


def test_external_worker_was_used(ran):
    assert ran.post_supervisor is not None
    assert ran.post_supervisor.alive()
    from spacemesh_tpu.post.remote import RemotePostClient

    for b in ran.atx_builders:
        assert isinstance(b.post_client, RemotePostClient)


def test_consensus_progressed_with_split_weight(ran):
    """With weight split over N identities, hare still reaches threshold
    (all identities vote) and blocks get applied."""
    applied = layerstore.last_applied(ran.state)
    assert applied >= LPE + 1
    assert any(blockstore.ids_in_layer(ran.state, lyr)
               for lyr in range(LPE, applied + 1))
